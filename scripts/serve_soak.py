"""Serving soak: N client threads hammer one QueryScheduler with mixed
TPC-DS-like query shapes under a constrained memory budget, measuring
end-to-end latency percentiles, shed rate, and peak in-flight concurrency.

Three shapes over a store_sales-like parquet fact table:
  agg    — two-stage hash agg (partial -> hash exchange -> final)
  sort   — global sort over a single-partition exchange + limit
  window — per-store rank() window over a hash exchange

Round 3 (multi-tenant QoS): three tenants share one scheduler — a
``flood`` tenant spamming far past capacity, a ``batch`` tenant, and a
high-weight ``light`` interactive tenant. The soak runs the light
workload once ISOLATED and once UNDER the flood and gates the loaded
light p99 at <= 1.5x isolated (weighted-fair queuing + stage-boundary
preemption are what hold that line). Admission is adaptive (MemManager
headroom + profile hints, no fixed concurrency), full queues answer with
``Backpressure`` carrying a drain-rate Retry-After — and the clients
HONOR it, so door give-ups ("shed_door", 12 in round 2) collapse. A
preemption probe pauses a multi-boundary query mid-plan under the flood
and proves it resumes bit-identical from its stage cursor. Per-tenant
percentiles, shed-reason breakdowns, and the preemption tripwires
(``queries_preempted``, ``stages_resumed_from_cursor``,
``backpressure_429s``) land in SERVE_r03.json at the repo root — the
numbers BASELINE.md cites. Client tallies are still reconciled EXACTLY
against the registry's counters, now summed across tenant labels.

Round 4 (--zipf, SERVE_r04.json): the result-cache soak — zipfian
repeats over ~20 query variants gate hit rate >= 0.5, warm hits >= 100x
faster than cold, zero stale serves, and the light tenant's p99 inside
round 3's envelope; a streaming section gates incremental refreshes
(>= 10x below the cold wall, bit-identical to full recompute). The
chaos matrix gains ``mid_ingest_kill`` (CHAOS_r03.json): worker kills
landing between append and refresh must never surface a stale or wrong
cached result.

Round 5 (--rate, SERVE_r05.json): the firehose — continuous appends at a
target rows/s under the full zipfian serve load, judged on the live
health plane (obs/timeline.py): ingest-lag series bounded and back to
<= 1 version within the drain window, zero stale serves, zero critical
health intervals, refreshed rollups bit-identical to full recomputes.

Run: python scripts/serve_soak.py   (CPU; ~2-4 min)
Env: SERVE_CLIENTS (64), SERVE_QUERIES (160 total), SERVE_CONCURRENT
(0 = adaptive admission), SERVE_BUDGET_MB (192), SERVE_ROWS (120_000),
SERVE_QUEUE (8), SERVE_QUEUE_TIMEOUT_S (30).
"""

import json
import math
import os
import random
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CLIENTS = int(os.environ.get("SERVE_CLIENTS", 64))
QUERIES = int(os.environ.get("SERVE_QUERIES", 160))
CONCURRENT = int(os.environ.get("SERVE_CONCURRENT", 0))  # 0 -> adaptive
BUDGET_MB = int(os.environ.get("SERVE_BUDGET_MB", 192))
ROWS = int(os.environ.get("SERVE_ROWS", 120_000))
QUEUE = int(os.environ.get("SERVE_QUEUE", 8))
QUEUE_TIMEOUT_S = float(os.environ.get("SERVE_QUEUE_TIMEOUT_S", 30.0))

import jax

jax.config.update("jax_platforms", "cpu")


def pctl(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1))))]


def _get(base, path):
    return urllib.request.urlopen(base + path, timeout=10).read().decode()


def _counter(raw_registry, name, **labels):
    """Exact integer SUM of the counter series matching ``labels`` as a
    SUBSET out of format=raw (0 when no series fired — drain/exposition
    skip empty series). Subset-sum, not exact-match: the serve counters
    grew a tenant label this round, so e.g. ``reason="queue_full"`` must
    aggregate over every tenant's series."""
    fam = raw_registry.get(name)
    if not fam:
        return 0
    total = 0
    for s in fam["series"]:
        sl = s.get("labels", {})
        if all(sl.get(k) == v for k, v in labels.items()):
            total += int(s["value"])
    return total


def shm_roots(baseline=()):
    """Zero-copy shm roots currently present, minus a baseline snapshot —
    sessions must unlink theirs at close, so any delta is a leak."""
    import glob

    return sorted(set(glob.glob("/dev/shm/blaze_tpu_shm_*")) - set(baseline))


def main():
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import (get_registry,
                                         histogram_quantiles_from_text,
                                         parse_prometheus_text)
    from blaze_tpu.ops.base import QueryCancelled
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.http import ProfilingService
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Backpressure, Overloaded, QueryScheduler

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG

    # flood: weight 1, 1 concurrent, 48 MB mem quota; batch: weight 2,
    # 1 concurrent; light: weight 8, uncapped — the interactive tenant the
    # soak gates on. Per-tenant concurrency caps keep any single heavy
    # tenant from holding every run slot; WFQ admits light heads first;
    # and stage-boundary preemption evicts a running heavy when a light
    # query is left waiting. Isolation is capacity reservation: the two
    # heavy tenants are capped at ONE slot each, and the adaptive
    # ceiling leaves enough surplus slots (18 - 2 = 16) for the light
    # tenant's entire client fleet to be in flight at once — a light
    # query never waits on capacity at all. Its loaded-vs-isolated
    # inflation is then bounded by the CPU-share ratio of the extra
    # heavy streams, (16 light + 2 heavy) / 16 ~= 1.13x, well inside
    # the 1.5x envelope on any box; preemption covers what caps cannot
    # — memory contention and bursts past the reserved headroom.
    TENANTS = "flood:1:1:48;batch:2:1;light:8"
    ADAPTIVE_CAP = max(18, os.cpu_count() or 1)
    LIGHT_Q = max(8, QUERIES * 30 // 100)
    BATCH_Q = max(8, QUERIES * 15 // 100)
    FLOOD_Q = max(1, QUERIES - LIGHT_Q - BATCH_Q)
    LIGHT_C = max(4, CLIENTS // 4)
    BATCH_C = max(4, CLIENTS // 8)
    FLOOD_C = max(1, CLIENTS - LIGHT_C - BATCH_C)

    out = {"clients": CLIENTS, "queries": QUERIES,
           "concurrent": CONCURRENT or "adaptive",
           "budget_mb": BUDGET_MB, "rows": ROWS, "tenants_spec": TENANTS,
           "mix": {"flood": {"clients": FLOOD_C, "queries": FLOOD_Q},
                   "batch": {"clients": BATCH_C, "queries": BATCH_Q},
                   "light": {"clients": LIGHT_C, "queries": LIGHT_Q}}}
    t_all = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="blaze_serve_soak_") as tmpdir:
        set_config(Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          serve_tenants=TENANTS,
                          serve_adaptive_max_concurrent=ADAPTIVE_CAP,
                          serve_preempt_after_s=0.02,
                          serve_preempt_min_run_s=0.02,
                          # the QoS soak measures EXECUTION under load; the
                          # result cache would turn the repeated shapes into
                          # microsecond hits and break the exact
                          # executed-outcome reconciliation below
                          # (--zipf is the cache soak, SERVE_r04.json)
                          cache_enabled=False,
                          # ~1 in 8 flood queries carries a HOPELESS
                          # deadline by design; a per-second miss-ratio
                          # spike of 1-in-2 is this soak's normal, so the
                          # serve SLO here judges sustained majority
                          # misses, not the injected ones
                          slo_specs=("serve:serve_deadline_miss_ratio<=0.5;"
                                     "cache:cache_stale_served_rate==0;"
                                     "ingest:ingest_lag_versions<=2;"
                                     "shuffle:shuffle_tier_degraded_rate==0;"
                                     "workers:worker_deaths_rate==0"),
                          timeline_interval_s=0.5,
                          incident_dir=os.path.join(tmpdir, "incidents"),
                          incident_max_bundles=64))
        MemManager.reset()

        # store_sales-like fact: (store, item, qty, price)
        rng = random.Random(7)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(ROWS)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(ROWS)],
            "ss_quantity": [rng.randrange(1, 100) for _ in range(ROWS)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(ROWS)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_plan():
            # sum(net_paid) group by store (Q3/Q7-style rollup)
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def sort_plan():
            # global top-1000 by net_paid (Q98-style ordered report) with
            # per-partition top-k pushdown: each scan partition keeps its
            # own top 1000, the single-partition stage merges 4k rows —
            # same result, and no stage hogs a full-table sort's worth of
            # CPU in one slice (that slice is what smears every
            # co-running tenant's tail on a small box)
            order = [E.SortOrder(E.Column("ss_net_paid"), ascending=False)]
            local = N.Limit(N.Sort(scan(), order), 1000)
            ex = N.ShuffleExchange(local, N.SinglePartitioning(1))
            return N.Limit(N.Sort(ex, order), 1000)

        def window_plan():
            # rank() over (partition by store order by net_paid) (Q67-style)
            ex = N.ShuffleExchange(
                scan(), N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Window(
                ex,
                [N.WindowExpr(kind="rank", name="rnk")],
                [E.Column("ss_store_sk")],
                [E.SortOrder(E.Column("ss_net_paid"), ascending=False)])

        def proof_plan():
            # two stage boundaries (hash exchange, then single-partition
            # exchange) before the final sort: plenty of commit points for
            # a pause to land mid-plan. The secondary sort key makes the
            # top-500 unique, so pyarrow table equality is exact.
            g = [("ss_item_sk", E.Column("ss_item_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex1 = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_item_sk")], 4))
            final = N.Agg(ex1, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])
            ex2 = N.ShuffleExchange(final, N.SinglePartitioning(1))
            srt = N.Sort(ex2, [
                E.SortOrder(E.Column("paid"), ascending=False),
                E.SortOrder(E.Column("ss_item_sk"), ascending=True)])
            return N.Limit(srt, 500)

        # explicit per-shape admission estimates (measured: peak engine
        # usage for these plans at SERVE_ROWS=120k is well under these —
        # whole-run peak is ~10 MB); the generic plan-based estimate is
        # sized for unknown clients. The light estimate must leave room
        # for the WHOLE light fleet inside the budget: 16 x 8 MB + two
        # heavy reservations = 176 MB under the 192 MB budget
        shapes_by_tenant = {
            "light": [("agg", agg_plan, 8 << 20)],
            "batch": [("window", window_plan, 24 << 20),
                      ("sort", sort_plan, 24 << 20)],
            "flood": [("agg", agg_plan, 12 << 20),
                      ("sort", sort_plan, 24 << 20),
                      ("window", window_plan, 24 << 20)],
        }

        mu = threading.Lock()

        def start_clients(sched, spec):
            """spec: {tenant: (nclients, nqueries)}. Starts the client
            threads and returns (counts, lat_ms, threads) — the caller
            joins. Clients HONOR Backpressure's Retry-After instead of
            backing off blind, and only give up (shed_door) after 40
            failed door attempts — patient enough to outlast a full
            drain of this finite run's backlog, so every residual
            shed_door is a genuine starvation signal, not an artifact
            of the client's own impatience."""
            counts = {t: {"completed": 0, "shed_door": 0, "shed_queued": 0,
                          "cancelled": 0, "failed": 0, "door_overloads": 0,
                          "backpressure_429s": 0} for t in spec}
            lat_ms = {t: [] for t in spec}
            seqs = {t: iter(range(n)) for t, (_c, n) in spec.items()}

            def client(cid, tenant):
                rngc = random.Random(100 + cid)
                shapes_t = shapes_by_tenant[tenant]
                seq_t = seqs[tenant]
                while True:
                    with mu:
                        i = next(seq_t, None)
                    if i is None:
                        return
                    name, mk, est = shapes_t[i % len(shapes_t)]
                    # ~1 in 8 flood queries carries a hopeless deadline:
                    # exercises mid-flight cancel + reclamation under QoS
                    deadline = 0.05 if (tenant == "flood" and i % 8 == 5) \
                        else None
                    h = None
                    for _attempt in range(40):
                        try:
                            h = sched.submit(mk(), deadline_s=deadline,
                                             mem_estimate=est,
                                             label=f"{tenant}_{name}_{i}",
                                             tenant=tenant)
                            break
                        except Backpressure as exc:
                            # the server said WHEN to come back: honoring
                            # Retry-After is what turns round 2's blind
                            # door give-ups into bounded waiting. Repeat
                            # 429s double the wait (Retry-After as the
                            # backoff BASE) — without that, 48 flooding
                            # clients re-knock so often that the door
                            # traffic itself eats the box
                            with mu:
                                counts[tenant]["door_overloads"] += 1
                                counts[tenant]["backpressure_429s"] += 1
                            time.sleep(
                                min(exc.retry_after_s
                                    * (2 ** min(_attempt, 3)), 2.0)
                                * rngc.uniform(0.8, 1.2))
                        except Overloaded:
                            with mu:
                                counts[tenant]["door_overloads"] += 1
                            time.sleep(rngc.uniform(0.1, 0.4))
                    if h is None:
                        with mu:
                            counts[tenant]["shed_door"] += 1
                        continue
                    try:
                        h.result(timeout=300)
                        # server-side sojourn (submit -> finish on the
                        # scheduler's clock): full e2e including queue
                        # wait, but free of this harness's own artifact —
                        # 60+ client threads on a small box wait in the
                        # OS runqueue just to stamp a wall clock, and at
                        # p99 that noise would swamp the policy under test
                        ms = (h.finished_at - h.submitted_at) * 1e3
                        with mu:
                            counts[tenant]["completed"] += 1
                            lat_ms[tenant].append(ms)
                    except Overloaded:
                        with mu:
                            counts[tenant]["shed_queued"] += 1
                    except QueryCancelled:
                        with mu:
                            counts[tenant]["cancelled"] += 1
                    except BaseException as exc:
                        print(f"[client {cid}] {tenant}_{name}_{i} failed: "
                              f"{type(exc).__name__}: {exc}",
                              file=sys.stderr)
                        with mu:
                            counts[tenant]["failed"] += 1
                    time.sleep(rngc.uniform(0, 0.02))

            threads, cid = [], 0
            for tenant, (nclients, _n) in spec.items():
                for _ in range(nclients):
                    threads.append(threading.Thread(
                        target=client, args=(cid, tenant), daemon=True))
                    cid += 1
            for t in threads:
                t.start()
            return counts, lat_ms, threads

        shm0 = shm_roots()
        with Session() as sess:
            from blaze_tpu.utils.device import DEVICE_STATS

            DEVICE_STATS.reset()
            svc = ProfilingService.start(sess)
            base = f"http://127.0.0.1:{svc.port}"
            scrape_errors = []
            stop_sampler = threading.Event()

            def sampler():
                # a live Prometheus would scrape mid-soak: prove /metrics
                # stays parseable and cheap under concurrent load
                while not stop_sampler.wait(1.0):
                    try:
                        parse_prometheus_text(_get(base, "/metrics"))
                    except Exception as exc:  # noqa: BLE001
                        scrape_errors.append(repr(exc))

            # JIT warmup + the preemption-proof oracle, engine-direct
            ref_proof = sess.execute_to_table(proof_plan(),
                                              release_on_finish=True)
            for mk in (agg_plan, sort_plan, window_plan):
                sess.execute_to_table(mk(), release_on_finish=True)

            # -- phase 1: the light tenant ISOLATED -----------------------
            get_registry().reset_values()
            with QueryScheduler(sess, max_concurrent=CONCURRENT or None,
                                max_queue=QUEUE,
                                queue_timeout_s=QUEUE_TIMEOUT_S) as sched:
                iso_counts, iso_lat, ts = start_clients(
                    sched, {"light": (LIGHT_C, LIGHT_Q)})
                for t in ts:
                    t.join()
            out["isolated_light"] = {
                "latency_ms": {"p50": pctl(iso_lat["light"], 50),
                               "p95": pctl(iso_lat["light"], 95),
                               "p99": pctl(iso_lat["light"], 99)},
                **iso_counts["light"]}

            # -- phase 2: same light workload UNDER the flood -------------
            get_registry().reset_values()
            probe = {"attempts": 0, "preempt_count": 0,
                     "bit_identical": False, "resumed_rows": None}
            try:
                with QueryScheduler(sess, max_concurrent=CONCURRENT or None,
                                    max_queue=QUEUE,
                                    queue_timeout_s=QUEUE_TIMEOUT_S) as sched:
                    counts, lat_ms, ts = start_clients(
                        sched, {"flood": (FLOOD_C, FLOOD_Q),
                                "batch": (BATCH_C, BATCH_Q),
                                "light": (LIGHT_C, LIGHT_Q)})

                    def preempt_probe():
                        # under the flood: pause a multi-boundary query
                        # mid-plan via the operator preempt API (policy
                        # preemption uses the same token) and prove the
                        # resumed result is bit-identical to the oracle
                        rngp = random.Random(4242)
                        for attempt in range(6):
                            probe["attempts"] = attempt + 1
                            h = None
                            while h is None:
                                try:
                                    h = sched.submit(
                                        proof_plan(),
                                        mem_estimate=24 << 20,
                                        label=f"preempt_proof_{attempt}",
                                        tenant="batch")
                                except Backpressure as exc:
                                    with mu:
                                        counts["batch"][
                                            "door_overloads"] += 1
                                        counts["batch"][
                                            "backpressure_429s"] += 1
                                    time.sleep(min(exc.retry_after_s, 2.0))
                                except Overloaded:
                                    with mu:
                                        counts["batch"][
                                            "door_overloads"] += 1
                                    time.sleep(rngp.uniform(0.1, 0.3))
                            # pre-arm the pause: poll preempt() from the
                            # moment of submission so the request lands
                            # between admission and the FIRST stage
                            # boundary (a fixed sleep races the whole
                            # query at small scales)
                            t_wait = time.monotonic() + 120
                            while time.monotonic() < t_wait:
                                if sched.preempt(h.qid,
                                                 "soak preempt proof"):
                                    break
                                if h.state in ("done", "failed",
                                               "cancelled", "shed"):
                                    break
                                time.sleep(0.002)
                            try:
                                got = h.result(timeout=300)
                            except Overloaded:
                                with mu:
                                    counts["batch"]["shed_queued"] += 1
                                continue
                            except BaseException as exc:
                                print(f"[probe] {type(exc).__name__}: "
                                      f"{exc}", file=sys.stderr)
                                with mu:
                                    counts["batch"]["failed"] += 1
                                return
                            with mu:
                                counts["batch"]["completed"] += 1
                            if h.preempt_count >= 1 \
                                    and got.equals(ref_proof):
                                probe["preempt_count"] = h.preempt_count
                                probe["bit_identical"] = True
                                probe["resumed_rows"] = got.num_rows
                                return

                    smp = threading.Thread(target=sampler, daemon=True)
                    smp.start()
                    prb = threading.Thread(target=preempt_probe,
                                           daemon=True)
                    prb.start()
                    for t in ts:
                        t.join()
                    prb.join()
                    stop_sampler.set()
                    smp.join(timeout=5)

                    # -- scrape while the scheduler is still open ---------
                    prom_text = _get(base, "/metrics")
                    parsed = parse_prometheus_text(prom_text)
                    raw = json.loads(_get(base, "/debug/metrics?format=raw"))
                    reg = raw["registry"]
                    incidents = json.loads(_get(base, "/debug/incidents"))
                    dl = [i for i in incidents if i["kind"] == "deadline"]
                    dl_bundle = (
                        json.loads(_get(
                            base, f"/debug/incidents/{dl[0]['id']}"))
                        if dl else None)
                    # stats plane: served queries leave fingerprint-keyed
                    # profiles; the artifact keeps the index head as proof
                    # the plane stays live under concurrency
                    profiles = json.loads(_get(base, "/debug/profiles"))

                    out["peak_inflight"] = sched.peak_inflight
                    out["admission"] = {"adaptive": sched.adaptive,
                                        "cap": sched.max_concurrent}
                    out["serve_metrics"] = sched.metrics.to_dict()
                    out["wfq_tenants"] = sched.snapshot()["tenants"]
                    out["query_profiles"] = {"count": len(profiles),
                                             "head": profiles[:3]}
            finally:
                ProfilingService.stop()

            assert not scrape_errors, scrape_errors

            # device + fusion counters next to the SLOs — the same
            # kernel_stats shape bench records (DEVICE_STATS snapshot merged
            # with the invariant tripwires, fused-stage jit cache included)
            from blaze_tpu.runtime.metrics import tripwire_totals

            out["kernel_stats"] = dict(DEVICE_STATS.snapshot(),
                                       **tripwire_totals(sess.metrics))

            # -- latency SLOs from the scraped histograms ------------------
            def hist_ms(name, **labels):
                qs = histogram_quantiles_from_text(
                    parsed, name, labels, [0.5, 0.95, 0.99])
                return {f"p{int(q * 100)}":
                        None if v is None else round(v * 1e3, 2)
                        for q, v in qs.items()}

            out["latency_ms"] = hist_ms("blaze_serve_e2e_seconds",
                                        outcome="done")
            out["run_ms"] = hist_ms("blaze_serve_run_seconds")
            out["tenants"] = {
                tname: {
                    "latency_ms": {"p50": pctl(lat_ms[tname], 50),
                                   "p95": pctl(lat_ms[tname], 95),
                                   "p99": pctl(lat_ms[tname], 99)},
                    "queue_wait_ms": hist_ms(
                        "blaze_serve_queue_wait_seconds", tenant=tname),
                    **counts[tname],
                } for tname in ("flood", "batch", "light")}

            # -- exact reconciliation: registry vs client ground truth -----
            tot = {k: sum(c[k] for c in counts.values())
                   for k in next(iter(counts.values()))}
            reg_counts = {
                "door_overloads": _counter(reg, "blaze_serve_rejected_total",
                                           reason="queue_full"),
                "backpressure": _counter(reg,
                                         "blaze_serve_backpressure_total"),
                "shed_queued": _counter(reg, "blaze_serve_queries_total",
                                        outcome="shed"),
                "completed": _counter(reg, "blaze_serve_queries_total",
                                      outcome="done"),
                "deadline": _counter(reg, "blaze_serve_queries_total",
                                     outcome="deadline"),
                "cancelled": _counter(reg, "blaze_serve_queries_total",
                                      outcome="cancelled"),
                "failed": _counter(reg, "blaze_serve_queries_total",
                                   outcome="failed"),
                "preempted": _counter(reg, "blaze_serve_preempted_total"),
                "stage_resumes": _counter(
                    reg, "blaze_serve_stage_resumes_total"),
            }
            recon = {
                "door_overloads": (tot["door_overloads"],
                                   reg_counts["door_overloads"]),
                "backpressure_429s": (tot["backpressure_429s"],
                                      reg_counts["backpressure"]),
                "shed_queued": (tot["shed_queued"],
                                reg_counts["shed_queued"]),
                "completed": (tot["completed"], reg_counts["completed"]),
                "cancelled": (tot["cancelled"],
                              reg_counts["deadline"]
                              + reg_counts["cancelled"]),
                "failed": (tot["failed"], reg_counts["failed"]),
            }
            mismatches = {k: v for k, v in recon.items() if v[0] != v[1]}
            assert not mismatches, (
                f"registry counters disagree with client truth "
                f"(client, registry): {mismatches}")
            out["registry_counts"] = reg_counts
            out["reconciled"] = {k: v[0] for k, v in recon.items()}

            # every accepted query must land in exactly one outcome bucket
            accepted_total = sum(
                int(s["value"])
                for s in reg["blaze_serve_queries_total"]["series"])
            assert accepted_total == (tot["completed"]
                                      + tot["shed_queued"]
                                      + tot["cancelled"]
                                      + tot["failed"]), accepted_total

            # -- the histogram must agree with the counters too ------------
            done_in_hist = sum(
                int(v) for labels, v in
                parsed.get("blaze_serve_e2e_seconds_count",
                           {}).get("samples", [])
                if labels.get("outcome") == "done")
            assert done_in_hist == tot["completed"], (
                done_in_hist, tot["completed"])

            # -- deadline forensics: bundle must be retrievable over HTTP --
            assert reg_counts["deadline"] > 0, \
                "soak never exercised the deadline path"
            assert dl, f"no deadline bundle among {len(incidents)} incidents"
            assert dl_bundle["spans"], "bundle is missing ring-buffer spans"
            assert dl_bundle["memmgr"] is not None
            out["incidents"] = {"total": len(incidents),
                                "deadline_bundle": dl[0]["id"],
                                "bundle_spans": len(dl_bundle["spans"])}

            out["tripwires"] = {
                "queries_preempted": reg_counts["preempted"],
                "stages_resumed_from_cursor": reg_counts["stage_resumes"],
                "backpressure_429s": reg_counts["backpressure"],
            }
            out["preempt_proof"] = probe

        mm = MemManager._instance
        out.update({
            "totals": tot,
            "spill_count": mm.spill_count if mm else 0,
            "peak_mem_used": mm.peak_used if mm else None,
            "leaked_mem": mm.used if mm else 0,
            "shm_segments_leaked": len(shm_roots(shm0)),
            "wall_s": round(time.perf_counter() - t_all, 2),
        })

    from blaze_tpu.obs.attribution import artifact_section
    from blaze_tpu.obs.timeline import timeline_artifact_section

    out.update(artifact_section())
    out.update(timeline_artifact_section())
    iso_p99 = out["isolated_light"]["latency_ms"]["p99"]
    light_p99 = out["tenants"]["light"]["latency_ms"]["p99"]
    out["gates"] = {
        "light_p99_isolated_ms": iso_p99,
        "light_p99_loaded_ms": light_p99,
        "light_p99_ratio": round(light_p99 / max(iso_p99, 1e-9), 3),
        "shed_door": tot["shed_door"],
        "shed_door_r02": 12,  # what round 2's blind clients gave up on
        "preempt_proof_bit_identical": probe["bit_identical"],
        "preempt_proof_count": probe["preempt_count"],
        "health_critical_intervals": out["health"]["critical_intervals"],
        "health_degraded_ratio": out["health"]["degraded_ratio"],
        **out["tripwires"],
    }
    dst = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVE_r03.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=2, default=str)
    print(json.dumps(out, indent=2, default=str))
    # evidence is on disk; now the QoS gates
    assert tot["failed"] == 0, "soak had hard failures"
    assert out["leaked_mem"] == 0, "memory leaked across queries"
    assert out["shm_segments_leaked"] == 0, "/dev/shm segment roots leaked"
    assert light_p99 <= 1.5 * iso_p99, (
        f"light tenant p99 {light_p99}ms under flood breached 1.5x its "
        f"isolated p99 {iso_p99}ms — WFQ failed to hold the line")
    assert tot["shed_door"] <= 4, (
        f"shed_door {tot['shed_door']} > 4: Retry-After backpressure "
        f"should cut round 2's 12 door give-ups by >= 3x")
    assert out["tripwires"]["queries_preempted"] >= 1, out["tripwires"]
    assert out["tripwires"]["stages_resumed_from_cursor"] >= 1, \
        out["tripwires"]
    assert probe["bit_identical"] and probe["preempt_count"] >= 1, probe
    # tracer-drop gate: a soak must never overflow the trace buffer (full
    # tracing stays off here, so any drop means the flight-recorder path or
    # a worker absorb went wrong)
    assert out["tracer_events_dropped"] == 0, (
        f"tracer dropped {out['tracer_events_dropped']} events during soak")
    # health-state HISTORY, not just the end state: no subsystem may have
    # spent a single interval critical, and non-healthy time stays bounded
    assert out["health"]["samples"] > 0, "timeline sampler never ran"
    assert out["health"]["critical_intervals"] == 0, out["health"]
    assert out["health"]["degraded_ratio"] <= 0.5, out["health"]
    print(f"\nwrote {dst}")


def zipf_main():
    """Cache serve soak (--zipf) -> SERVE_r04.json: a ``heavy`` tenant's
    clients draw from ~20 dashboard-query variants with zipfian
    popularity — exactly the repeated-fingerprint traffic the result
    cache (blaze_tpu/cache/) exists for — while a ``light`` tenant issues
    UNIQUE-fingerprint queries that always execute, so its p99 measures
    real execution latency in both phases. Gates: overall hit rate
    >= 0.5, every heavy result (cache-served or not) equal to an
    engine-direct oracle, zero stale serves, the light tenant's loaded
    p99 inside SERVE_r03's 1.5x envelope (cache traffic must not starve
    execution), and a warm/cold probe proving a repeated query returns
    >= 100x faster than its cold execution, already done at submit
    return. A streaming section then proves incremental maintenance:
    appends to an ingest table turn the cached aggregate stale, each
    refresh recomputes only the appended tail (median refresh >= 10x
    below the cold wall) and stays bit-identical to a full recompute.
    Client tallies reconcile exactly against the registry, with
    ``cache_hit`` a first-class outcome. Env: same SERVE_* family as the
    plain soak."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.base import QueryCancelled
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Backpressure, Overloaded, QueryScheduler

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG

    VARIANTS = 20
    ADAPTIVE_CAP = max(18, os.cpu_count() or 1)
    HEAVY_C = max(4, CLIENTS * 3 // 4)
    LIGHT_C = max(4, CLIENTS - HEAVY_C)
    HEAVY_Q = max(40, QUERIES * 3 // 4)
    LIGHT_Q = max(16, QUERIES - HEAVY_Q)
    # zipf(s=1.1) popularity over the variant ranks: the head variant is
    # drawn ~20x as often as the tail — a realistic dashboard skew where
    # a >= 0.5 hit rate only needs each variant executed once
    WEIGHTS = [1.0 / (r + 1) ** 1.1 for r in range(VARIANTS)]

    out = {"clients": CLIENTS, "queries": QUERIES, "budget_mb": BUDGET_MB,
           "rows": ROWS, "variants": VARIANTS, "zipf_s": 1.1,
           "mix": {"heavy": {"clients": HEAVY_C, "queries": HEAVY_Q},
                   "light": {"clients": LIGHT_C, "queries": LIGHT_Q}}}
    t_all = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="blaze_serve_zipf_") as tmpdir:
        set_config(Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          serve_tenants="heavy:1:2;light:8",
                          serve_adaptive_max_concurrent=ADAPTIVE_CAP,
                          incident_dir=os.path.join(tmpdir, "incidents")))
        MemManager.reset()

        rng = random.Random(7)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(ROWS)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(ROWS)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(ROWS)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_over(filt):
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(filt, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def variant_plan(i):
            # the i-th dashboard variant: same rollup, different item
            # threshold — distinct canonical fingerprint per variant
            return agg_over(N.Filter(scan(), [E.BinaryExpr(
                E.BinaryOp.LT, E.Column("ss_item_sk"),
                E.Literal(100 + i * 90, T.I64))]))

        def unique_plan(j):
            # pass-all predicate with a UNIQUE literal: a fingerprint no
            # earlier query shares, so the cache always misses and the
            # query always executes — the light tenant's latency (and the
            # cold half of the warm/cold probe) measures real execution
            return agg_over(N.Filter(scan(), [E.BinaryExpr(
                E.BinaryOp.GT, E.Column("ss_item_sk"),
                E.Literal(-1 - j, T.I64))]))

        def canon(table):
            d = table.to_pydict()
            return sorted(zip(*d.values())) if d else []

        mu = threading.Lock()

        def run_clients(sched, spec, oracle, uniq_base):
            """spec: {tenant: (nclients, nqueries)}. Heavy clients draw
            variants zipfian and check results against the oracle; light
            clients burn unique fingerprints from ``uniq_base``."""
            counts = {t: {"completed": 0, "shed_queued": 0, "cancelled": 0,
                          "failed": 0, "door_overloads": 0} for t in spec}
            lat_ms = {t: [] for t in spec}
            wrong = []
            seqs = {t: iter(range(n)) for t, (_c, n) in spec.items()}

            def client(cid, tenant):
                rngc = random.Random(300 + cid)
                seq_t = seqs[tenant]
                while True:
                    with mu:
                        i = next(seq_t, None)
                    if i is None:
                        return
                    if tenant == "heavy":
                        v = rngc.choices(range(VARIANTS),
                                         weights=WEIGHTS)[0]
                        mk, est = (lambda v=v: variant_plan(v)), 12 << 20
                        label = f"heavy_v{v}_{i}"
                    else:
                        v = None
                        mk, est = (lambda j=uniq_base + i:
                                   unique_plan(j)), 8 << 20
                        label = f"light_u{i}"
                    h = None
                    for _attempt in range(40):
                        try:
                            h = sched.submit(mk(), mem_estimate=est,
                                             label=label, tenant=tenant)
                            break
                        except Backpressure as exc:
                            with mu:
                                counts[tenant]["door_overloads"] += 1
                            time.sleep(
                                min(exc.retry_after_s
                                    * (2 ** min(_attempt, 3)), 2.0)
                                * rngc.uniform(0.8, 1.2))
                        except Overloaded:
                            with mu:
                                counts[tenant]["door_overloads"] += 1
                            time.sleep(rngc.uniform(0.1, 0.4))
                    if h is None:
                        with mu:
                            counts[tenant]["failed"] += 1
                        continue
                    try:
                        got = h.result(timeout=300)
                        ms = (h.finished_at - h.submitted_at) * 1e3
                        with mu:
                            counts[tenant]["completed"] += 1
                            lat_ms[tenant].append(ms)
                            if v is not None and canon(got) != oracle[v]:
                                wrong.append({"variant": v, "query": i})
                    except Overloaded:
                        with mu:
                            counts[tenant]["shed_queued"] += 1
                    except QueryCancelled:
                        with mu:
                            counts[tenant]["cancelled"] += 1
                    except BaseException as exc:
                        print(f"[client {cid}] {label} failed: "
                              f"{type(exc).__name__}: {exc}",
                              file=sys.stderr)
                        with mu:
                            counts[tenant]["failed"] += 1
                    time.sleep(rngc.uniform(0, 0.02))

            threads, cid = [], 0
            for tenant, (nclients, _n) in spec.items():
                for _ in range(nclients):
                    threads.append(threading.Thread(
                        target=client, args=(cid, tenant), daemon=True))
                    cid += 1
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return counts, lat_ms, wrong

        shm0 = shm_roots()
        with Session() as sess:
            # engine-direct oracles + JIT warmup (warmup plans use the
            # unique-fingerprint family so they never seed the cache the
            # soak is about to measure)
            oracle = {i: canon(sess.execute_to_table(
                variant_plan(i), release_on_finish=True))
                for i in range(VARIANTS)}
            sess.cache.clear(reason="closed")

            # -- phase 1: the light tenant ISOLATED -----------------------
            get_registry().reset_values()
            with QueryScheduler(sess, max_concurrent=CONCURRENT or None,
                                max_queue=QUEUE,
                                queue_timeout_s=QUEUE_TIMEOUT_S) as sched:
                iso_counts, iso_lat, _w = run_clients(
                    sched, {"light": (LIGHT_C, LIGHT_Q)}, oracle,
                    uniq_base=0)
            out["isolated_light"] = {
                "latency_ms": {"p50": pctl(iso_lat["light"], 50),
                               "p95": pctl(iso_lat["light"], 95),
                               "p99": pctl(iso_lat["light"], 99)},
                **iso_counts["light"]}

            # -- phase 2: zipfian heavy traffic + the same light load -----
            sess.cache.clear(reason="closed")
            get_registry().reset_values()
            probe = {}
            with QueryScheduler(sess, max_concurrent=CONCURRENT or None,
                                max_queue=QUEUE,
                                queue_timeout_s=QUEUE_TIMEOUT_S) as sched:
                counts, lat_ms, wrong = run_clients(
                    sched, {"heavy": (HEAVY_C, HEAVY_Q),
                            "light": (LIGHT_C, LIGHT_Q)}, oracle,
                    uniq_base=10_000)

                # -- warm/cold probe, scheduler still open ----------------
                # cold: a never-seen fingerprint, timed on the scheduler's
                # own clock; warm: the SAME plan resubmitted — the submit
                # call itself must return a finished handle (the hit
                # bypasses admission, queue, and executor entirely)
                h1 = sched.submit(unique_plan(99_999), mem_estimate=8 << 20,
                                  label="probe_cold")
                cold_table = h1.result(timeout=300)
                cold_s = h1.finished_at - h1.submitted_at
                t0 = time.perf_counter()
                h2 = sched.submit(unique_plan(99_999), mem_estimate=8 << 20,
                                  label="probe_warm")
                warm_s = time.perf_counter() - t0
                probe = {"cold_ms": round(cold_s * 1e3, 3),
                         "warm_us": round(warm_s * 1e6, 1),
                         "warm_done_at_submit": h2.done(),
                         "warm_bit_identical":
                             h2.result(timeout=5).equals(cold_table),
                         "speedup": round(cold_s / max(warm_s, 1e-9), 1)}

                reg = get_registry().to_raw()
                out["cache"] = dict(sess.cache.stats_fields())
                out["serve_metrics"] = sched.metrics.to_dict()
                out["cache_snapshot_entries"] = \
                    sched.snapshot()["cache"]["counts"]

            # -- reconciliation: every accepted query in ONE outcome ------
            tot = {k: sum(c[k] for c in counts.values())
                   for k in next(iter(counts.values()))}
            tot["completed"] += 2  # the probe's two queries
            accepted_total = sum(
                int(s["value"])
                for s in reg["blaze_serve_queries_total"]["series"])
            assert accepted_total == (tot["completed"] + tot["shed_queued"]
                                      + tot["cancelled"] + tot["failed"]), \
                (accepted_total, tot)
            hits = _counter(reg, "blaze_serve_queries_total",
                            outcome="cache_hit")
            executed = _counter(reg, "blaze_serve_queries_total",
                                outcome="done")
            out["totals"] = tot
            out["hit_rate"] = round(hits / max(hits + executed, 1), 3)
            out["tenants"] = {
                tname: {
                    "latency_ms": {"p50": pctl(lat_ms[tname], 50),
                                   "p95": pctl(lat_ms[tname], 95),
                                   "p99": pctl(lat_ms[tname], 99)},
                    **counts[tname],
                } for tname in ("heavy", "light")}
            out["wrong_results"] = wrong
            out["warm_cold_probe"] = probe

        # -- streaming section: incremental maintenance under appends ----
        stream = {"history_rows": 0, "appends": [], "cold_ms": None}
        with Session() as sess:
            hist = []
            for _ in range(24):
                hist.append(pa.RecordBatch.from_pydict({
                    "k": [rng.randrange(16) for _ in range(5000)],
                    "v": [rng.randrange(1000) for _ in range(5000)]}))
            sess.append("stream", hist, num_partitions=4)
            stream["history_rows"] = 24 * 5000
            g = [("k", E.Column("k"))]
            partial = N.Agg(sess.table_scan("stream"), HASH, g,
                            [N.AggColumn(
                                E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("k")], 4))
            plan = N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                M.FINAL, "paid")])
            t0 = time.perf_counter()
            got = sess.execute_cached(plan)
            stream["cold_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
            bit_identical = True
            for r in range(8):
                sess.append("stream", [pa.RecordBatch.from_pydict({
                    "k": [rng.randrange(16) for _ in range(2000)],
                    "v": [rng.randrange(1000) for _ in range(2000)]})])
                t0 = time.perf_counter()
                got = sess.execute_cached(plan)
                refresh_ms = round((time.perf_counter() - t0) * 1e3, 2)
                full = sess.execute_to_table(plan, release_on_finish=True)
                same = canon(got) == canon(full)
                bit_identical = bit_identical and same
                stream["appends"].append(
                    {"round": r, "refresh_ms": refresh_ms,
                     "bit_identical": same})
            stream["cache"] = dict(sess.cache.stats_fields())
            refreshes = sorted(a["refresh_ms"] for a in stream["appends"])
            stream["median_refresh_ms"] = refreshes[len(refreshes) // 2]
            stream["refresh_speedup"] = round(
                stream["cold_ms"] / max(stream["median_refresh_ms"], 1e-6),
                1)
            stream["bit_identical"] = bit_identical
        out["stream"] = stream

        mm = MemManager._instance
        out.update({
            "leaked_mem": mm.used if mm else 0,
            "shm_segments_leaked": len(shm_roots(shm0)),
            "wall_s": round(time.perf_counter() - t_all, 2),
        })

    from blaze_tpu.obs.attribution import artifact_section
    from blaze_tpu.obs.timeline import timeline_artifact_section

    out.update(artifact_section())
    out.update(timeline_artifact_section())
    iso_p99 = out["isolated_light"]["latency_ms"]["p99"]
    light_p99 = out["tenants"]["light"]["latency_ms"]["p99"]
    out["gates"] = {
        "cache_hit_rate": out["hit_rate"],
        "cache_hits": hits,
        "cache_misses": out["cache"]["cache_misses"],
        "cache_stale_served": out["cache"]["cache_stale_served"],
        "light_p99_isolated_ms": iso_p99,
        "light_p99_loaded_ms": light_p99,
        "light_p99_ratio": round(light_p99 / max(iso_p99, 1e-9), 3),
        "cold_ms": probe["cold_ms"],
        "warm_hit_us": probe["warm_us"],
        "warm_speedup": probe["speedup"],
        "warm_done_at_submit": probe["warm_done_at_submit"],
        "stream_refresh_speedup": stream["refresh_speedup"],
        "stream_bit_identical": stream["bit_identical"],
        "wrong_results": len(wrong),
        "failed": tot["failed"],
        "leaked_mem": out["leaked_mem"],
        "shm_segments_leaked": out["shm_segments_leaked"],
        "health_critical_intervals": out["health"]["critical_intervals"],
        "health_degraded_ratio": out["health"]["degraded_ratio"],
    }
    dst = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVE_r04.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=2, default=str)
    print(json.dumps(out["gates"], indent=2, default=str))
    # evidence is on disk; now the cache gates
    g = out["gates"]
    assert g["failed"] == 0, "soak had hard failures"
    assert g["wrong_results"] == 0, wrong
    assert g["cache_stale_served"] == 0, g
    assert g["cache_hit_rate"] >= 0.5, (
        f"hit rate {g['cache_hit_rate']} < 0.5 under zipfian repeats "
        f"({hits} hits / {executed} executions)")
    # SERVE_r03's QoS envelope, with a small absolute floor: when both
    # percentiles sit in the tens of milliseconds, scheduler jitter on a
    # loaded box can exceed 1.5x without any starvation
    assert light_p99 <= max(1.5 * iso_p99, iso_p99 + 25.0), (
        f"light tenant p99 {light_p99}ms under cache traffic breached "
        f"1.5x its isolated p99 {iso_p99}ms")
    assert g["warm_done_at_submit"], probe
    assert probe["warm_bit_identical"], probe
    assert g["warm_speedup"] >= 100, (
        f"warm hit only {g['warm_speedup']}x faster than cold "
        f"({probe['warm_us']}us vs {probe['cold_ms']}ms)")
    assert g["stream_bit_identical"], stream["appends"]
    assert g["stream_refresh_speedup"] >= 10, (
        f"median incremental refresh {stream['median_refresh_ms']}ms is "
        f"not 10x below the {stream['cold_ms']}ms cold wall")
    assert stream["cache"]["cache_refreshes"] >= 8, stream["cache"]
    assert g["leaked_mem"] == 0, "memory leaked across queries"
    assert g["shm_segments_leaked"] == 0, "/dev/shm segment roots leaked"
    assert out["health"]["samples"] > 0, "timeline sampler never ran"
    assert g["health_critical_intervals"] == 0, out["health"]
    assert g["health_degraded_ratio"] <= 0.5, out["health"]
    print(f"\nwrote {dst}")


def rate_main(rows_per_s: int):
    """Firehose streaming soak (--rate) -> SERVE_r05.json: an appender
    thread streams batches into an ingest table at a target rows/s for
    RATE_DURATION_S while the full client fleet serves cached mergeable
    rollups over that same table through one QueryScheduler, drawn
    zipfian over ~16 variants. Every append stales the hot entries;
    every hit-after-stale takes the incremental refresh path — the
    ROADMAP "streaming soak appending at rate under continuous serving"
    round, judged on the TIMELINE (obs/timeline.py), not end state:
    the ingest-lag series must stay bounded and return to <= 1 version
    within the drain window after the appender stops, zero stale
    results served, zero ``critical`` health intervals, and refreshed
    results bit-identical to full recomputes over the final table.
    Env: RATE_DURATION_S (20), RATE_BATCH_ROWS (5000), RATE_DRAIN_S (6),
    SERVE_CLIENTS / SERVE_BUDGET_MB as the other rounds."""
    import pyarrow as pa

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.obs.timeline import get_timeline
    from blaze_tpu.ops.base import QueryCancelled
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Backpressure, Overloaded, QueryScheduler

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG
    duration_s = float(os.environ.get("RATE_DURATION_S", 20.0))
    drain_s = float(os.environ.get("RATE_DRAIN_S", 6.0))
    batch_rows = int(os.environ.get("RATE_BATCH_ROWS", 5000))
    append_interval = batch_rows / max(rows_per_s, 1)
    VARIANTS = 16
    WEIGHTS = [1.0 / (r + 1) ** 1.1 for r in range(VARIANTS)]
    ADAPTIVE_CAP = max(18, os.cpu_count() or 1)

    out = {"target_rows_per_s": rows_per_s, "duration_s": duration_s,
           "drain_s": drain_s, "batch_rows": batch_rows,
           "clients": CLIENTS, "variants": VARIANTS, "zipf_s": 1.1,
           "budget_mb": BUDGET_MB}
    t_all = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="blaze_serve_rate_") as tmpdir:
        set_config(Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          serve_tenants="dash:8",
                          serve_adaptive_max_concurrent=ADAPTIVE_CAP,
                          # fine-grained lag/backlog curves: the sampler
                          # IS the instrument this round is judged by
                          timeline_interval_s=0.25,
                          # bounded-staleness contract, expressed in
                          # versions at the configured append rate: a
                          # rollup may trail the stream by up to ~10s of
                          # appends under full load (lag tracks queue
                          # latency — refreshes cover the versions seen
                          # at execution start), but must never be
                          # SERVED stale and must drain to <= 1 once
                          # appends stop (the hard gates below)
                          slo_specs=("serve:serve_deadline_miss_ratio<=0.5;"
                                     "cache:cache_stale_served_rate==0;"
                                     f"ingest:ingest_lag_versions<="
                                     f"{max(4, math.ceil(10.0 / append_interval))};"
                                     "shuffle:shuffle_tier_degraded_rate==0;"
                                     "workers:worker_deaths_rate==0"),
                          incident_dir=os.path.join(tmpdir, "incidents"),
                          incident_max_bundles=64))
        MemManager.reset()

        rng = random.Random(7)

        def mk_batch():
            return pa.RecordBatch.from_pydict({
                "k": [rng.randrange(16) for _ in range(batch_rows)],
                "v": [rng.randrange(1000) for _ in range(batch_rows)]})

        # a small pool of pre-built batches cycled by the appender: the
        # soak measures the ENGINE's append+refresh pipeline, not Python
        # row generation
        pool = [mk_batch() for _ in range(8)]

        def variant_plan(i):
            # i-th dashboard rollup: SUM(v) by k over keys <= i — the
            # filter sits BELOW the output agg, so every variant is
            # mergeable (incremental.mergeable_spec) and refreshes from
            # the appended tail alone
            filt = N.Filter(sess.table_scan("stream"), [E.BinaryExpr(
                E.BinaryOp.LTEQ, E.Column("k"), E.Literal(i, T.I64))])
            g = [("k", E.Column("k"))]
            partial = N.Agg(filt, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("k")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                M.FINAL, "paid")])

        def canon(table):
            d = table.to_pydict()
            return sorted(zip(*d.values())) if d else []

        mu = threading.Lock()
        shm0 = shm_roots()
        with Session() as sess:
            # seed history + JIT warmup (through the same variant shapes)
            sess.append("stream", [mk_batch() for _ in range(12)],
                        num_partitions=4)
            out["history_rows"] = 12 * batch_rows
            # JIT warmup + cache pre-fill: every variant lands a FRESH
            # entry BEFORE the firehose starts, so the soak measures the
            # steady state — serves finding stale entries and folding
            # the appended tail in via incremental refresh. (A cold fill
            # racing the appender is discarded by the epoch guard, so a
            # cleared cache under a continuous firehose never converges.)
            for i in range(VARIANTS):
                sess.execute_cached(variant_plan(i))
            get_registry().reset_values()
            get_timeline().reset()

            appender = {"rows": 0, "appends": 0, "behind_s": 0.0,
                        "t_start": None, "t_end": None}
            counts = {"completed": 0, "failed": 0, "shed": 0,
                      "cancelled": 0, "door_overloads": 0}
            lat_ms = []
            stop_clients = threading.Event()

            def append_loop():
                appender["t_start"] = time.time()
                next_t = time.perf_counter()
                end = next_t + duration_s
                i = 0
                while time.perf_counter() < end:
                    sess.append("stream", [pool[i % len(pool)]])
                    i += 1
                    appender["appends"] += 1
                    appender["rows"] += batch_rows
                    next_t += append_interval
                    sleep = next_t - time.perf_counter()
                    if sleep > 0:
                        time.sleep(sleep)
                    else:
                        # the box cannot sustain the target: record how
                        # far behind the pacer fell instead of silently
                        # redefining the rate
                        appender["behind_s"] = max(
                            appender["behind_s"], -sleep)
                appender["t_end"] = time.time()

            def client(cid):
                rngc = random.Random(500 + cid)
                while not stop_clients.is_set():
                    v = rngc.choices(range(VARIANTS), weights=WEIGHTS)[0]
                    h = None
                    for _attempt in range(40):
                        if stop_clients.is_set():
                            return
                        try:
                            h = sched.submit(variant_plan(v),
                                             mem_estimate=12 << 20,
                                             label=f"dash_v{v}",
                                             tenant="dash")
                            break
                        except Backpressure as exc:
                            with mu:
                                counts["door_overloads"] += 1
                            time.sleep(min(exc.retry_after_s
                                           * (2 ** min(_attempt, 3)), 2.0)
                                       * rngc.uniform(0.8, 1.2))
                        except Overloaded:
                            with mu:
                                counts["door_overloads"] += 1
                            time.sleep(rngc.uniform(0.05, 0.2))
                    if h is None:
                        continue
                    try:
                        h.result(timeout=300)
                        with mu:
                            counts["completed"] += 1
                            # cache hits finish the handle AT submit, so
                            # the two stamps can land microseconds apart
                            # in either order — clamp to zero
                            lat_ms.append(max(
                                0.0,
                                (h.finished_at - h.submitted_at) * 1e3))
                    except Overloaded:
                        with mu:
                            counts["shed"] += 1
                    except QueryCancelled:
                        with mu:
                            counts["cancelled"] += 1
                    except BaseException as exc:
                        print(f"[client {cid}] dash_v{v} failed: "
                              f"{type(exc).__name__}: {exc}",
                              file=sys.stderr)
                        with mu:
                            counts["failed"] += 1
                    time.sleep(rngc.uniform(0, 0.01))

            with QueryScheduler(sess, max_concurrent=CONCURRENT or None,
                                max_queue=QUEUE,
                                queue_timeout_s=QUEUE_TIMEOUT_S) as sched:
                threads = [threading.Thread(target=client, args=(c,),
                                            daemon=True)
                           for c in range(CLIENTS)]
                for t in threads:
                    t.start()
                app = threading.Thread(target=append_loop, daemon=True)
                app.start()
                app.join()
                # drain window: serving continues with NO new appends —
                # this is where the lag series must fall back to <= 1
                time.sleep(drain_s)
                stop_clients.set()
                for t in threads:
                    t.join()

                # freshness proof over the FINAL table: the cached (and
                # possibly tail-refreshed many times over) rollup must be
                # bit-identical to a from-scratch recompute
                freshness = []
                for i in (0, 3, VARIANTS - 1):
                    got = sess.execute_cached(variant_plan(i))
                    full = sess.execute_to_table(variant_plan(i),
                                                 release_on_finish=True)
                    freshness.append({"variant": i,
                                      "bit_identical":
                                          canon(got) == canon(full)})
                out["freshness"] = freshness
                # one settled sample past the final refreshes, so the
                # artifact's lag curve ends on the drained state
                time.sleep(0.6)

                reg = get_registry().to_raw()
                out["cache"] = dict(sess.cache.stats_fields())
                out["lag_probe"] = sess.cache.ingest_lag_probe()
                out["serve_metrics"] = sched.metrics.to_dict()
                out["peak_inflight"] = sched.peak_inflight

            wall = (appender["t_end"] or time.time()) \
                - (appender["t_start"] or time.time())
            out["appender"] = dict(appender)
            out["achieved_rows_per_s"] = round(
                appender["rows"] / max(wall, 1e-9))
            out["totals"] = dict(counts)
            out["latency_ms"] = {"p50": pctl(lat_ms, 50),
                                 "p95": pctl(lat_ms, 95),
                                 "p99": pctl(lat_ms, 99)}
            out["hits"] = _counter(reg, "blaze_serve_queries_total",
                                   outcome="cache_hit")
            out["executed"] = _counter(reg, "blaze_serve_queries_total",
                                       outcome="done")
            out["stale_served_registry"] = _counter(
                reg, "blaze_cache_stale_total", result="served")
            out["ingest_appends_registry"] = _counter(
                reg, "blaze_ingest_appends_total", table="stream")
            out["ingest_rows_registry"] = _counter(
                reg, "blaze_ingest_rows_total", table="stream")

        mm = MemManager._instance
        out.update({
            "leaked_mem": mm.used if mm else 0,
            "shm_segments_leaked": len(shm_roots(shm0)),
            "wall_s": round(time.perf_counter() - t_all, 2),
        })

    from blaze_tpu.obs.attribution import artifact_section
    from blaze_tpu.obs.timeline import timeline_artifact_section

    out.update(artifact_section())
    out.update(timeline_artifact_section())
    lag_series = out["timeline"].get("ingest_lag_versions") or []
    lag_values = [v for _t, v in lag_series]
    backlog = out["timeline"].get("cache_refresh_backlog_count") or []
    out["gates"] = {
        "achieved_rows_per_s": out["achieved_rows_per_s"],
        "appends": out["appender"]["appends"],
        "pacer_behind_s": round(out["appender"]["behind_s"], 3),
        "lag_max_versions": max(lag_values, default=0),
        "lag_final_versions": lag_values[-1] if lag_values else None,
        "refresh_backlog_max": max((v for _t, v in backlog), default=0),
        "stale_served": out["stale_served_registry"],
        "cache_stale_served": out["cache"]["cache_stale_served"],
        "refreshes": out["cache"]["cache_refreshes"],
        "completed": out["totals"]["completed"],
        "failed": out["totals"]["failed"],
        "freshness_ok": all(f["bit_identical"]
                            for f in out["freshness"]),
        "health_critical_intervals": out["health"]["critical_intervals"],
        "health_degraded_ratio": out["health"]["degraded_ratio"],
        "leaked_mem": out["leaked_mem"],
        "shm_segments_leaked": out["shm_segments_leaked"],
    }
    dst = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVE_r05.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=2, default=str)
    print(json.dumps(out["gates"], indent=2, default=str))
    # evidence is on disk; now the firehose gates
    g = out["gates"]
    assert g["failed"] == 0, "soak had hard failures"
    assert g["completed"] > 0 and g["appends"] > 0, g
    # the firehose actually induced staleness the cache had to absorb...
    assert g["lag_max_versions"] >= 1 or g["refresh_backlog_max"] >= 1, g
    # ...and absorbed it: the lag series returned to <= 1 version once
    # appends stopped (the drain window is the gate window)
    assert g["lag_final_versions"] is not None \
        and g["lag_final_versions"] <= 1, g
    assert out["lag_probe"]["ingest_lag_versions"] <= 1, out["lag_probe"]
    assert g["stale_served"] == 0 and g["cache_stale_served"] == 0, g
    assert g["refreshes"] >= 1, g
    assert g["freshness_ok"], out["freshness"]
    assert out["health"]["samples"] > 0, "timeline sampler never ran"
    assert g["health_critical_intervals"] == 0, out["health"]
    assert g["health_degraded_ratio"] <= 0.5, out["health"]
    assert g["leaked_mem"] == 0, "memory leaked across queries"
    assert g["shm_segments_leaked"] == 0, "/dev/shm segment roots leaked"
    assert out["tracer_events_dropped"] == 0, out["tracer_events_dropped"]
    print(f"\nwrote {dst}")


def chaos_main(kill_every_s: float):
    """Serve chaos soak (--chaos-kill-every): clients hammer a 2-worker
    clustered scheduler while a ChaosMonkey hard-kills a random worker every
    ``kill_every_s`` seconds. Worker loss mid-query is absorbed by task retry
    + respawn; a query that exhausts its retry budget surfaces as the typed
    ``QueryRetryable`` (incident id attached) and the client RESUBMITS it.
    Gates: zero wrong results, zero hard failures, zero leaked memory bytes,
    worker deaths observed with incident bundles retrievable over HTTP at
    ``/debug/incidents``, chaos p99 <= 3x the no-chaos baseline p99. Evidence
    merges into CHAOS_r01.json (section "serve") BEFORE gates are asserted.
    Env: CHAOS_ROWS (200_000), CHAOS_QUERIES (24), CHAOS_CLIENTS (4).
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.cluster import ChaosMonkey
    from blaze_tpu.runtime.http import ProfilingService
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Overloaded, QueryRetryable, QueryScheduler
    from scale_soak import _pctl, _write_chaos_section

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG
    rows = int(os.environ.get("CHAOS_ROWS", 200_000))
    queries = int(os.environ.get("CHAOS_QUERIES", 24))
    clients = int(os.environ.get("CHAOS_CLIENTS", 4))

    COUNTERS = ("blaze_cluster_worker_deaths_total",
                "blaze_cluster_tasks_retried_total",
                "blaze_cluster_stages_recovered_total",
                "blaze_cluster_maps_recomputed_total",
                "blaze_chaos_kills_total")

    def counters() -> dict:
        snap = get_registry().to_raw()
        out = {}
        for name in COUNTERS:
            series = snap.get(name, {}).get("series", [])
            out[name] = sum(s["value"] for s in series)
        return out

    section = {"kill_every_s": kill_every_s, "rows": rows,
               "queries": queries, "clients": clients, "phases": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_serve_chaos_") as tmpdir:
        rng = random.Random(11)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(rows)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(rows)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(rows)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_plan():
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def sort_plan():
            ex = N.ShuffleExchange(scan(), N.SinglePartitioning(1))
            srt = N.Sort(ex, [E.SortOrder(E.Column("ss_net_paid"),
                                          ascending=False)])
            return N.Limit(srt, 1000)

        def window_plan():
            ex = N.ShuffleExchange(
                scan(), N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Window(
                ex,
                [N.WindowExpr(kind="rank", name="rnk")],
                [E.Column("ss_store_sk")],
                [E.SortOrder(E.Column("ss_net_paid"), ascending=False)])

        def canon_rows(table):
            d = table.to_pydict()
            return sorted(zip(*d.values())) if d else []

        def canon_sort(table):
            # ties at the limit boundary make the exact top-1000 row set
            # attempt-dependent; the sort-key multiset is deterministic
            return sorted(table["ss_net_paid"].to_pylist())

        shapes = [("agg", agg_plan, 12 << 20, canon_rows),
                  ("sort", sort_plan, 24 << 20, canon_sort),
                  ("window", window_plan, 24 << 20, canon_rows)]

        with Session() as s_local:
            oracle = {name: cn(s_local.execute_to_table(mk()))
                      for name, mk, _e, cn in shapes}

        def run_phase(with_chaos: bool) -> dict:
            MemManager.reset()
            conf = Config(
                memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                mem_wait_timeout_s=5.0,
                cache_enabled=False,  # chaos measures recovery, not reuse
                incident_dir=os.path.join(
                    tmpdir,
                    "incidents_chaos" if with_chaos else "incidents_base"))
            set_config(conf)
            lats, wrong, hard_failures, retryable_ids = [], [], [], []
            tallies = {"completed": 0, "resubmits": 0, "gave_up": 0}
            mu = threading.Lock()
            seq = iter(range(queries))
            http_incidents, http_bundle = [], None
            shm0 = shm_roots()
            with Session(conf=conf, num_worker_processes=2) as sess:
                svc = ProfilingService.start(sess) if with_chaos else None
                monkey = ChaosMonkey(sess.pool, kill_every_s,
                                     seed=13).start() if with_chaos else None
                try:
                    with QueryScheduler(sess, max_concurrent=2, max_queue=8,
                                        queue_timeout_s=60.0) as sched:
                        def client(cid):
                            rngc = random.Random(200 + cid)
                            while True:
                                with mu:
                                    i = next(seq, None)
                                if i is None:
                                    return
                                name, mk, est, cn = shapes[i % len(shapes)]
                                t0 = time.perf_counter()
                                got = None
                                for _attempt in range(5):
                                    try:
                                        h = sched.submit(
                                            mk(), mem_estimate=est,
                                            label=f"{name}_{i}")
                                        got = h.result(timeout=300)
                                        break
                                    except Overloaded:
                                        time.sleep(rngc.uniform(0.05, 0.2))
                                    except QueryRetryable as exc:
                                        # the typed retryable contract: the
                                        # client just resubmits
                                        with mu:
                                            tallies["resubmits"] += 1
                                            if exc.incident_id:
                                                retryable_ids.append(
                                                    exc.incident_id)
                                    except BaseException as exc:
                                        with mu:
                                            hard_failures.append(
                                                f"{name}_{i}: "
                                                f"{type(exc).__name__}: "
                                                f"{exc}")
                                        return
                                with mu:
                                    if got is None:
                                        tallies["gave_up"] += 1
                                        return
                                    tallies["completed"] += 1
                                    lats.append(time.perf_counter() - t0)
                                    if cn(got) != oracle[name]:
                                        wrong.append(
                                            {"query": i, "shape": name})

                        ts = [threading.Thread(target=client, args=(c,),
                                               daemon=True)
                              for c in range(clients)]
                        for t in ts:
                            t.start()
                        for t in ts:
                            t.join()
                finally:
                    if monkey is not None:
                        monkey.stop()
                        time.sleep(2.0)  # heartbeat grace for the last kill
                    if svc is not None:
                        # the ISSUE's contract: every killed worker's bundle
                        # is retrievable over HTTP under /debug/incidents
                        base_url = f"http://127.0.0.1:{svc.port}"
                        all_inc = json.loads(_get(base_url,
                                                  "/debug/incidents"))
                        http_incidents = [b for b in all_inc
                                          if b["kind"] == "worker_lost"]
                        if http_incidents:
                            http_bundle = json.loads(_get(
                                base_url, "/debug/incidents/"
                                f"{http_incidents[0]['id']}"))
                        ProfilingService.stop()
                kills = list(monkey.kills) if monkey else []
                mm = MemManager._instance
                leaked = int(mm.used) if mm is not None else 0
            return {
                "lat_s": [round(v, 4) for v in lats],
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                **tallies,
                "wrong_results": wrong,
                "hard_failures": hard_failures,
                "retryable_incident_ids": retryable_ids,
                "kills_injected": len(kills),
                "kills": kills,
                "incident_bundles_worker_lost": len(http_incidents),
                "bundle_has_wid": bool(http_bundle
                                       and "wid" in http_bundle["extra"]),
                "leaked_mem": leaked,
                "shm_segments_leaked": len(shm_roots(shm0)),
            }

        section["phases"]["baseline"] = base = run_phase(with_chaos=False)
        c1 = counters()
        section["phases"]["chaos"] = chaos = run_phase(with_chaos=True)
        c2 = counters()
        section["counters_delta_chaos"] = {k: c2[k] - c1[k] for k in COUNTERS}

    d = section["counters_delta_chaos"]
    section["gates"] = gates = {
        "wrong_results": len(base["wrong_results"])
        + len(chaos["wrong_results"]),
        "hard_failures": len(base["hard_failures"])
        + len(chaos["hard_failures"]),
        "gave_up": base["gave_up"] + chaos["gave_up"],
        "leaked_bytes": base["leaked_mem"] + chaos["leaked_mem"],
        "shm_segments_leaked": base["shm_segments_leaked"]
        + chaos["shm_segments_leaked"],
        "worker_deaths_total": d["blaze_cluster_worker_deaths_total"],
        "kills_injected": chaos["kills_injected"],
        "incident_bundles": chaos["incident_bundles_worker_lost"],
        "p99_no_chaos_s": base["p99_s"],
        "p99_chaos_s": chaos["p99_s"],
        "p99_inflation": round(chaos["p99_s"] / max(base["p99_s"], 1e-9), 2),
    }
    from blaze_tpu.obs.attribution import artifact_section

    section.update(artifact_section())
    path = _write_chaos_section("serve", section)
    print(json.dumps({"gates": gates, "artifact": path}), flush=True)

    assert gates["wrong_results"] == 0, gates
    assert gates["hard_failures"] == 0, (gates,
                                         chaos["hard_failures"],
                                         base["hard_failures"])
    assert gates["gave_up"] == 0, gates
    assert gates["leaked_bytes"] == 0, gates
    assert gates["shm_segments_leaked"] == 0, gates
    assert gates["worker_deaths_total"] > 0, gates
    assert gates["kills_injected"] > 0, gates
    assert gates["incident_bundles"] >= gates["kills_injected"], gates
    assert chaos["bundle_has_wid"], "bundle must identify the lost worker"
    assert gates["p99_chaos_s"] <= 3.0 * gates["p99_no_chaos_s"], gates
    print("CHAOS SOAK (serve) PASSED", flush=True)


def chaos_matrix_main(spec: str):
    """Serve chaos matrix (--chaos-spec
    kill:N,hang:N,enospc:N,corrupt:N,preempt:N,mid_ingest_kill:N): client
    threads hammer a
    2-worker clustered scheduler once uninjected, then once per requested
    injection mode. EVERY mode gates on zero wrong results, zero
    client-visible failures (the serve layer's auto-retry must absorb
    worker loss — clients never see ``QueryRetryable``), zero leaked
    memory bytes / shm roots, and p99 <= 2x the uninjected phase; plus the
    same per-mode evidence as the scale matrix. ``preempt`` is the
    preemption storm: aggressive stage-boundary preemption plus a delay
    failpoint at every boundary commit — its evidence is queries actually
    preempted AND resumed from their stage cursors, its correctness gate
    is the same zero-wrong-results / zero-leaks bar (the p99 bound is
    waived: a storm deliberately delays its victims).

    ``mid_ingest_kill`` (ISSUE 19) is the cache-enabled phase: it
    hard-kills a worker between a streaming ``append`` and the
    incremental refresh that follows, and gates on the cache epoch
    discarding every kill-spanning computation — zero wrong results
    against a running oracle, zero stale results served, zero stale
    entries surviving, and a deterministic refused-offer proof. When the
    spec requests it the artifact lands in CHAOS_r03.json instead.

    A deterministic retry-proof prologue runs first: a query whose first
    execution is forced (``worker.task=ioerror`` failpoint, x-capped) to
    exhaust the pool's task retry budget MUST complete via the scheduler's
    transparent re-execution, with the retry recorded on the handle.
    Evidence lands in CHAOS_r02.json (section "serve") BEFORE gates are
    asserted. Env: CHAOS_ROWS (200_000), CHAOS_QUERIES (24),
    CHAOS_CLIENTS (4).
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime import failpoints
    from blaze_tpu.runtime.cluster import ChaosMonkey
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Overloaded, QueryRetryable, QueryScheduler
    from scale_soak import (_pctl, _write_chaos_section,
                            chaos_mode_conf_kwargs, parse_chaos_spec)

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG
    modes = parse_chaos_spec(spec)
    rows = int(os.environ.get("CHAOS_ROWS", 200_000))
    queries = int(os.environ.get("CHAOS_QUERIES", 24))
    clients = int(os.environ.get("CHAOS_CLIENTS", 4))

    COUNTERS = ("blaze_cluster_worker_deaths_total",
                "blaze_cluster_tasks_retried_total",
                "blaze_cluster_tasks_timed_out_total",
                "blaze_cluster_maps_recomputed_total",
                "blaze_serve_retries_total",
                "blaze_serve_preempted_total",
                "blaze_serve_stage_resumes_total",
                "blaze_chaos_kills_total")

    def counters() -> dict:
        # sum across series: the serve counters are tenant-labeled now
        snap = get_registry().to_raw()
        out = {}
        for name in COUNTERS:
            series = snap.get(name, {}).get("series", [])
            out[name] = sum(s["value"] for s in series)
        return out

    section = {"spec": spec, "rows": rows, "queries": queries,
               "clients": clients, "phases": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_serve_chaosm_") as tmpdir:
        rng = random.Random(11)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(rows)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(rows)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(rows)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_plan():
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def sort_plan():
            ex = N.ShuffleExchange(scan(), N.SinglePartitioning(1))
            srt = N.Sort(ex, [E.SortOrder(E.Column("ss_net_paid"),
                                          ascending=False)])
            return N.Limit(srt, 1000)

        def canon_rows(table):
            d = table.to_pydict()
            return sorted(zip(*d.values())) if d else []

        def canon_sort(table):
            # ties at the limit boundary make the exact top-1000 row set
            # attempt-dependent; the sort-key multiset is deterministic
            return sorted(table["ss_net_paid"].to_pylist())

        shapes = [("agg", agg_plan, 12 << 20, canon_rows),
                  ("sort", sort_plan, 24 << 20, canon_sort)]

        with Session() as s_local:
            oracle = {name: cn(s_local.execute_to_table(mk()))
                      for name, mk, _e, cn in shapes}

        # -- deterministic serve-retry proof -----------------------------
        # x6 per worker: with 4 map tasks and a 3-attempt budget, 12 fires
        # guarantee one task fails 3 attempts on the FIRST execution
        # (TaskFailed), and the caps are spent before the scheduler's
        # transparent re-execution, which must then succeed
        MemManager.reset()
        proof_conf = Config(
            incident_dir=os.path.join(tmpdir, "incidents_proof"),
            cache_enabled=False,  # the proof needs a REAL re-execution
            failpoints="worker.task=ioerror:every1:x6", failpoint_seed=7)
        set_config(proof_conf)
        c0 = counters()
        with Session(conf=proof_conf, num_worker_processes=2) as sess:
            with QueryScheduler(sess, max_concurrent=1) as sched:
                h = sched.submit(agg_plan(), label="retry_proof")
                table = h.result(timeout=180)  # QueryRetryable = hard fail
        failpoints.disarm()
        c1 = counters()
        section["retry_proof"] = proof = {
            "serve_retries": len(h.retries),
            "retry_history": h.retries,
            "serve_retries_counter_delta":
                c1["blaze_serve_retries_total"]
                - c0["blaze_serve_retries_total"],
            "correct": canon_rows(table) == oracle["agg"],
        }
        print(json.dumps({"retry_proof": proof}), flush=True)

        def run_phase(mode, n) -> dict:
            MemManager.reset()
            kwargs = dict(chaos_mode_conf_kwargs(mode, n)) if mode else {}
            arm_spec = kwargs.pop("failpoints", "")
            arm_timeout = kwargs.pop("task_timeout_s", 0.0)
            conf = Config(
                memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                mem_wait_timeout_s=5.0,
                # repeated shapes would otherwise be served from cache and
                # starve the injections of executions to land in
                # (mid_ingest_kill is the cache-enabled chaos phase)
                cache_enabled=False,
                incident_dir=os.path.join(
                    tmpdir, f"incidents_{mode or 'baseline'}"), **kwargs)
            set_config(conf)
            lats, wrong, hard_failures = [], [], []
            tallies = {"completed": 0, "client_visible_retryable": 0,
                       "gave_up": 0}
            mu = threading.Lock()
            seq = iter(range(queries))
            shm0 = shm_roots()
            c0 = counters()
            with Session(conf=conf, num_worker_processes=2) as sess:
                # warmup pass: uninjected, but RECORDED in every phase's
                # latency population alike — worker JIT warmup is part of
                # each phase's tail in both the baseline and injected runs
                for name, mk, _e, cn in shapes:
                    t0 = time.perf_counter()
                    if cn(sess.execute_to_table(mk())) != oracle[name]:
                        wrong.append({"query": "warmup", "shape": name})
                    lats.append(time.perf_counter() - t0)
                if arm_spec:
                    conf.failpoints = arm_spec
                    conf.task_timeout_s = arm_timeout
                    failpoints.arm_from(conf)
                monkey = ChaosMonkey(sess.pool, n, seed=13).start() \
                    if mode == "kill" else None
                try:
                    with QueryScheduler(sess, max_concurrent=2, max_queue=8,
                                        queue_timeout_s=60.0) as sched:
                        def client(cid):
                            rngc = random.Random(200 + cid)
                            while True:
                                with mu:
                                    i = next(seq, None)
                                if i is None:
                                    return
                                name, mk, est, cn = shapes[i % len(shapes)]
                                t0 = time.perf_counter()
                                got = None
                                for _attempt in range(5):
                                    try:
                                        h = sched.submit(
                                            mk(), mem_estimate=est,
                                            label=f"{name}_{i}")
                                        got = h.result(timeout=300)
                                        break
                                    except Overloaded:
                                        time.sleep(rngc.uniform(0.05, 0.2))
                                    except QueryRetryable:
                                        # the auto-retry contract: clients
                                        # must never see this now
                                        with mu:
                                            tallies[
                                                "client_visible_retryable"
                                            ] += 1
                                    except BaseException as exc:
                                        with mu:
                                            hard_failures.append(
                                                f"{name}_{i}: "
                                                f"{type(exc).__name__}: "
                                                f"{exc}")
                                        return
                                with mu:
                                    if got is None:
                                        tallies["gave_up"] += 1
                                        return
                                    tallies["completed"] += 1
                                    lats.append(time.perf_counter() - t0)
                                    if cn(got) != oracle[name]:
                                        wrong.append(
                                            {"query": i, "shape": name})

                        ts = [threading.Thread(target=client, args=(c,),
                                               daemon=True)
                              for c in range(clients)]
                        for t in ts:
                            t.start()
                        for t in ts:
                            t.join()
                finally:
                    if monkey is not None:
                        monkey.stop()
                        time.sleep(2.0)  # heartbeat grace for the last kill
                    failpoints.unhang()
                kills = list(monkey.kills) if monkey else []
                tier_degraded = int(sess.metrics.total(
                    "shuffle_tier_degraded"))
                mm = MemManager._instance
                leaked = int(mm.used) if mm is not None else 0
            failpoints.disarm()
            c1 = counters()
            return {
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                **tallies,
                "wrong_results": wrong,
                "hard_failures": hard_failures,
                "kills_injected": len(kills),
                "shuffle_tier_degraded": tier_degraded,
                "leaked_mem": leaked,
                "shm_segments_leaked": len(shm_roots(shm0)),
                "counters_delta": {k: c1[k] - c0[k] for k in COUNTERS},
            }

        def run_mid_ingest_kill(n) -> dict:
            """Streaming-ingest chaos: a 2-worker session serves a cached
            mergeable aggregation over an append-only ingest table while a
            worker is hard-killed between every ``n``-th append and the
            refresh that follows it. The cache epoch (manual bumps +
            ``pool.deaths_total``) must discard any entry whose execution
            spanned a kill: gates are zero wrong results against a running
            python oracle, zero stale results served, zero stale entries
            left in the cache, and a deterministic epoch-discard proof
            (an offer stamped with the pre-kill epoch is refused)."""
            from collections import defaultdict

            MemManager.reset()
            conf = Config(
                memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                mem_wait_timeout_s=5.0,
                fault_exclusion_ttl_s=0.5,
                incident_dir=os.path.join(tmpdir, "incidents_mik"))
            set_config(conf)
            kill_every = max(int(n), 2)
            iters = max(queries // 2, 10)
            lats, wrong, hard_failures = [], [], []
            oracle_sums = defaultdict(int)
            rng2 = random.Random(77)
            shm0 = shm_roots()
            c0 = counters()

            def mk_batch(nrows=2000):
                ks = [rng2.randrange(16) for _ in range(nrows)]
                vs = [rng2.randrange(1000) for _ in range(nrows)]
                for k, v in zip(ks, vs):
                    oracle_sums[k] += v
                return pa.RecordBatch.from_pydict({"k": ks, "v": vs})

            def canon(table):
                return sorted(zip(table["k"].to_pylist(),
                                  table["paid"].to_pylist()))

            def expect():
                return sorted(oracle_sums.items())

            def epoch_evictions() -> int:
                snap = get_registry().to_raw()
                series = snap.get("blaze_cache_evictions_total",
                                  {}).get("series", [])
                return sum(s["value"] for s in series
                           if s.get("labels", {}).get("reason") == "epoch")

            ev0 = epoch_evictions()
            kills = 0
            stats = {}
            with Session(conf=conf, num_worker_processes=2) as sess:
                sess.append("stream", [mk_batch() for _ in range(4)],
                            num_partitions=4)
                g = [("k", E.Column("k"))]
                partial = N.Agg(sess.table_scan("stream"), HASH, g,
                                [N.AggColumn(
                                    E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                                    M.PARTIAL, "paid")])
                ex = N.ShuffleExchange(
                    partial, N.HashPartitioning([E.Column("k")], 4))
                plan = N.Agg(ex, HASH, g, [N.AggColumn(
                    E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                    M.FINAL, "paid")])
                try:
                    if canon(sess.execute_cached(plan)) != expect():
                        wrong.append({"query": "seed"})
                    for i in range(iters):
                        sess.append("stream", [mk_batch()])
                        if i % kill_every == 0:
                            sess.pool.kill_worker(
                                rng2.randrange(len(sess.pool.workers)))
                            kills += 1
                        t0 = time.perf_counter()
                        got = sess.execute_cached(plan)
                        lats.append(time.perf_counter() - t0)
                        if canon(got) != expect():
                            wrong.append({"query": i})
                    # deterministic epoch-discard proof: wait out the
                    # supervisor's detection of one more kill, then offer a
                    # result stamped with the PRE-kill epoch — the cache
                    # must refuse it (an execution that spanned a worker
                    # death may have been built mid-recovery)
                    proof_plan = sess.table_scan("stream")
                    t0 = sess.cache.fill_token(proof_plan)
                    e0 = t0[0]
                    sess.pool.kill_worker(
                        rng2.randrange(len(sess.pool.workers)))
                    kills += 1
                    deadline = time.monotonic() + 30
                    while sess.cache.epoch() == e0 \
                            and time.monotonic() < deadline:
                        time.sleep(0.05)
                    sess.cache.offer(proof_plan,
                                     sess.execute_to_table(proof_plan), t0)
                    discard_proof = (
                        sess.cache.epoch() != e0
                        and sess.cache.serve(proof_plan) is None)
                except BaseException as exc:  # noqa: BLE001
                    hard_failures.append(f"{type(exc).__name__}: {exc}")
                    discard_proof = False
                time.sleep(2.0)  # heartbeat grace for the last kill
                with sess.cache._mu:
                    stale_surviving = sum(
                        0 if sess.cache._fresh_locked(e) else 1
                        for e in sess.cache._results.values())
                stats = dict(sess.cache.stats_fields())
            mm = MemManager._instance
            c1 = counters()
            return {
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                "completed": len(lats),
                "client_visible_retryable": 0,
                "gave_up": 0,
                "wrong_results": wrong,
                "hard_failures": hard_failures,
                "kills_injected": kills,
                "shuffle_tier_degraded": 0,
                "leaked_mem": int(mm.used) if mm is not None else 0,
                "shm_segments_leaked": len(shm_roots(shm0)),
                "counters_delta": {k: c1[k] - c0[k] for k in COUNTERS},
                "cache": stats,
                "cache_epoch_evictions": epoch_evictions() - ev0,
                "epoch_discard_proof": discard_proof,
                "stale_entries_surviving": stale_surviving,
            }

        section["phases"]["baseline"] = base = run_phase(None, 0)
        for mode, n in modes.items():
            section["phases"][mode] = run_mid_ingest_kill(n) \
                if mode == "mid_ingest_kill" else run_phase(mode, n)

    gates = {"p99_baseline_s": base["p99_s"],
             "retry_proof_serve_retries": proof["serve_retries"],
             "retry_proof_correct": proof["correct"], "modes": {}}
    for mode in modes:
        ph = section["phases"][mode]
        d = ph["counters_delta"]
        gates["modes"][mode] = {
            "wrong_results": len(ph["wrong_results"]),
            "hard_failures": len(ph["hard_failures"]),
            "client_visible_retryable": ph["client_visible_retryable"],
            "gave_up": ph["gave_up"],
            "leaked_bytes": ph["leaked_mem"],
            "shm_segments_leaked": ph["shm_segments_leaked"],
            "p99_s": ph["p99_s"],
            "p99_inflation": round(ph["p99_s"] / max(base["p99_s"], 1e-9),
                                   2),
            "worker_deaths": d["blaze_cluster_worker_deaths_total"],
            "tasks_timed_out": d["blaze_cluster_tasks_timed_out_total"],
            "maps_recomputed": d["blaze_cluster_maps_recomputed_total"],
            "serve_retries": d["blaze_serve_retries_total"],
            "queries_preempted": d["blaze_serve_preempted_total"],
            "stage_resumes": d["blaze_serve_stage_resumes_total"],
            "shuffle_tier_degraded": ph["shuffle_tier_degraded"],
            "kills_injected": ph["kills_injected"],
        }
        if mode == "mid_ingest_kill":
            gates["modes"][mode].update({
                "cache_stale_served": ph["cache"].get(
                    "cache_stale_served", 0),
                "cache_refreshes": ph["cache"].get("cache_refreshes", 0),
                "cache_epoch_evictions": ph["cache_epoch_evictions"],
                "stale_entries_surviving": ph["stale_entries_surviving"],
                "epoch_discard_proof": ph["epoch_discard_proof"],
            })
    section["gates"] = gates
    from blaze_tpu.obs.attribution import artifact_section

    section.update(artifact_section())
    fname = "CHAOS_r03.json" if "mid_ingest_kill" in modes \
        else "CHAOS_r02.json"
    path = _write_chaos_section("serve", section, fname=fname)
    print(json.dumps({"gates": gates, "artifact": path}), flush=True)

    # evidence is on disk; now enforce the matrix gates
    assert proof["serve_retries"] >= 1 and proof["correct"], proof
    assert proof["serve_retries_counter_delta"] >= 1, proof
    for mode in modes:
        g = gates["modes"][mode]
        assert g["wrong_results"] == 0, (mode, g)
        assert g["hard_failures"] == 0, (mode, g,
                                         section["phases"][mode]
                                         ["hard_failures"])
        assert g["client_visible_retryable"] == 0, (mode, g)
        assert g["gave_up"] == 0, (mode, g)
        assert g["leaked_bytes"] == 0, (mode, g)
        assert g["shm_segments_leaked"] == 0, (mode, g)
        if mode not in ("preempt", "mid_ingest_kill"):
            # a preemption storm deliberately parks victims at stage
            # boundaries, and the ingest-kill phase measures recovery
            # refreshes against a warmup-free baseline — their bar is
            # correctness + hygiene, not latency
            assert g["p99_s"] <= 2.0 * gates["p99_baseline_s"], (mode, g)
    if "kill" in modes:
        g = gates["modes"]["kill"]
        assert g["kills_injected"] > 0 and g["worker_deaths"] > 0, g
    if "hang" in modes:
        assert gates["modes"]["hang"]["tasks_timed_out"] > 0, gates
    if "enospc" in modes:
        assert gates["modes"]["enospc"]["shuffle_tier_degraded"] > 0, gates
    if "corrupt" in modes:
        assert gates["modes"]["corrupt"]["maps_recomputed"] > 0, gates
    if "preempt" in modes:
        g = gates["modes"]["preempt"]
        assert g["queries_preempted"] > 0, gates
        assert g["stage_resumes"] > 0, gates
    if "mid_ingest_kill" in modes:
        g = gates["modes"]["mid_ingest_kill"]
        assert g["kills_injected"] > 0 and g["worker_deaths"] > 0, g
        assert g["cache_stale_served"] == 0, g
        assert g["stale_entries_surviving"] == 0, g
        assert g["epoch_discard_proof"], g
    print("CHAOS MATRIX (serve) PASSED", flush=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--zipf", action="store_true",
                    help="cache serve soak: zipfian repeats over ~20 query "
                         "variants against the result cache, plus a "
                         "streaming incremental-maintenance section "
                         "(SERVE_r04.json) instead of the plain serve soak")
    ap.add_argument("--chaos-kill-every", type=float, metavar="N",
                    help="chaos mode: hard-kill a random worker every N "
                         "seconds under serving load and gate on recovery "
                         "(CHAOS_r01.json) instead of the plain serve soak")
    ap.add_argument("--rate", type=int, nargs="?", const=50_000,
                    metavar="ROWS_PER_S",
                    help="firehose streaming soak: continuously append at "
                         "the target rows/s (default 50000) to an ingest "
                         "table under the full zipfian serve load, gated "
                         "on the timeline's ingest-lag and stale-served "
                         "series and on health-state history "
                         "(SERVE_r05.json) instead of the plain serve soak")
    ap.add_argument("--chaos-spec", metavar="SPEC",
                    help="chaos matrix: comma-separated modes "
                         "kill:N,hang:N,enospc:N,corrupt:N,preempt:N,"
                         "mid_ingest_kill:N — one injected phase per mode "
                         "plus an uninjected baseline, gated per mode "
                         "(CHAOS_r02.json; CHAOS_r03.json when the spec "
                         "includes mid_ingest_kill)")
    args = ap.parse_args()
    if args.zipf:
        zipf_main()
    elif args.rate is not None:
        rate_main(args.rate)
    elif args.chaos_spec:
        chaos_matrix_main(args.chaos_spec)
    elif args.chaos_kill_every:
        chaos_main(args.chaos_kill_every)
    else:
        main()

"""Profile one TPC-DS-like bench query end to end: run it with span tracing
enabled and write the three observability artifacts to a directory:

- ``<query>_trace.json``    — Chrome trace events (load in
  https://ui.perfetto.dev or chrome://tracing): query/stage/task/operator/
  spill/shuffle-fetch/kernel spans on one timeline
- ``<query>_metrics.json``  — the full session metric tree, ``*_time_ns``
  values rendered as human durations
- ``<query>_explain.txt``   — EXPLAIN ANALYZE text (per-operator rows,
  batches, self-time, spill counters)

On plans whose aggregation takes the radix-partitioned device path, the
per-pass ``radix_bucket_histogram`` trace instants are additionally folded
into ``<query>_radix_hist.json`` — a skew summary (rows/groups per radix
bucket) alongside the raw instants Perfetto renders on the timeline.

Run: ``python scripts/profile_query.py [q01|q06|q17|q47|q67] [-o OUTDIR]``
Env: BENCH_ROWS (default 200_000 here — profiling wants fast iterations),
BENCH_PARTITIONS (4), SOAK-style knobs via the usual bench envs.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BENCH_ROWS", "200000")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("query", nargs="?", default="q01",
                    choices=["q01", "q06", "q17", "q47", "q67"])
    ap.add_argument("-o", "--out-dir", default="profile_out",
                    help="artifact directory (default: ./profile_out)")
    args = ap.parse_args()

    import bench  # repo-root bench.py (data generators + plan builders)
    from blaze_tpu.config import Config
    from blaze_tpu.obs import dump_profile
    from blaze_tpu.runtime.session import Session

    plan_fn = {"q01": bench.plan_q01, "q06": bench.plan_q06,
               "q17": bench.plan_q17, "q47": bench.plan_q47,
               "q67": bench.plan_q67}[args.query]

    with tempfile.TemporaryDirectory(prefix="blaze_profile_") as tmpdir:
        paths = bench.make_data(tmpdir)
        conf = Config(trace_enable=True)
        t0 = time.perf_counter()
        with Session(conf=conf) as sess:
            explain_text = sess.explain_analyze(plan_fn(paths))
            wall = time.perf_counter() - t0
            artifacts = dump_profile(sess, args.out_dir, args.query,
                                     explain_text=explain_text)
    hist = _radix_histogram(artifacts["trace"])
    if hist is not None:
        hist_path = os.path.join(args.out_dir,
                                 f"{args.query}_radix_hist.json")
        with open(hist_path, "w") as f:
            json.dump(hist, f, indent=1)
        artifacts["radix_hist"] = hist_path
    print(explain_text)
    print(json.dumps({"query": args.query, "wall_s": round(wall, 2),
                      "artifacts": artifacts}, indent=2))


def _radix_histogram(trace_path):
    """Fold the per-pass ``radix_bucket_histogram`` instants into one skew
    summary: total rows/groups per radix bucket across every pass, plus the
    heaviest buckets (the Perfetto timeline shows the per-pass instants;
    this answers "is one bucket hot" at a glance)."""
    with open(trace_path) as f:
        trace = json.load(f)
    passes = [ev.get("args", {})
              for ev in trace.get("traceEvents", [])
              if ev.get("name") == "radix_bucket_histogram"]
    passes = [a for a in passes if a.get("rows")]
    if not passes:
        return None
    nbuck = max(len(a["rows"]) for a in passes)
    rows = [0] * nbuck
    groups = [0] * nbuck
    for a in passes:
        for i, (r, g) in enumerate(zip(a["rows"], a["groups"])):
            rows[i] += int(r)
            groups[i] += int(g)
    total = sum(rows) or 1
    top = sorted(range(nbuck), key=lambda i: -rows[i])[:8]
    return {
        "passes": len(passes),
        "buckets": nbuck,
        "rows_total": sum(rows),
        "groups_total": sum(groups),
        "max_bucket_row_share": round(max(rows) / total, 4),
        "top_buckets": [{"bucket": i, "rows": rows[i], "groups": groups[i]}
                        for i in top],
        "rows": rows,
        "groups": groups,
    }


if __name__ == "__main__":
    main()

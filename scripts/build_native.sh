#!/bin/sh
# Build the native host-kernel library (native/ -> native/build/libblaze_native.so)
set -e
cd "$(dirname "$0")/.."
cmake -S native -B native/build -DCMAKE_BUILD_TYPE=Release
cmake --build native/build -- -j2
echo "built: native/build/libblaze_native.so"
